"""Fleet-scale tick guarantees (docs/design/tick-scale.md):

1. **API-request budget** — one engine tick issues O(kinds) LISTs and zero
   per-VA GETs on the happy path, asserted against FakeCluster's request
   counters for a 20-VA fleet (pre-change: 3+ GETs per VA per tick).
2. **Determinism under parallelism** — a multi-model engine-integration run
   produces byte-identical decisions, VA statuses, and flight-recorder
   cycle records with the analysis worker pool at 1 and at 8.
3. **Solver batching** — sizing every model's candidates in one batched
   call returns the same capacities as per-model calls.
4. **Snapshot client semantics** — cached reads, read-your-writes,
   targeted refresh, conflict-refetch status writes.
"""

from __future__ import annotations

import json

import pytest

from wva_tpu.api import ObjectMeta, VariantAutoscaling, VariantAutoscalingSpec
from wva_tpu.api.v1alpha1 import CrossVersionObjectReference
from wva_tpu.blackbox.schema import encode
from wva_tpu.collector.source import TimeSeriesDB
from wva_tpu.config import new_test_config
from wva_tpu.config.config import TraceConfig
from wva_tpu.interfaces import SaturationScalingConfig
from wva_tpu.k8s import (
    Container,
    Deployment,
    DeploymentStatus,
    FakeCluster,
    Pod,
    PodStatus,
    PodTemplateSpec,
    ResourceRequirements,
)
from wva_tpu.k8s.client import ConflictError
from wva_tpu.k8s.objects import FrozenObjectError, clone
from wva_tpu.k8s.snapshot import SnapshotKubeClient
from wva_tpu.main import build_manager
from wva_tpu.utils import FakeClock
from wva_tpu.utils.variant import update_va_status_with_conflict_refetch

NS = "inf"


def make_fleet_world(n_models: int, kv: float = 0.3, queue: int = 0,
                     saturation_cfg: SaturationScalingConfig | None = None,
                     analysis_workers: int | None = None,
                     trace: bool = False, informer: bool = True,
                     incremental: bool = True, fp_delta: bool = True,
                     fp_assert: bool = False):
    """FakeCluster world with ``n_models`` models, one VA/Deployment/pod
    each, live metrics in the TSDB, and a wired manager."""
    clock = FakeClock(start=100_000.0)
    cluster = FakeCluster(clock=clock)
    tsdb = TimeSeriesDB(clock=clock)
    cfg = new_test_config()
    cfg.update_saturation_config(
        {"default": saturation_cfg or SaturationScalingConfig()})
    if analysis_workers is not None:
        cfg.infrastructure.engine_analysis_workers = analysis_workers
    cfg.infrastructure.informer = informer
    cfg.infrastructure.incremental = incremental
    cfg.infrastructure.fp_delta = fp_delta
    cfg.infrastructure.fp_assert = fp_assert
    if trace:
        cfg.set_trace(TraceConfig(enabled=True))

    for i in range(n_models):
        name = f"m{i:03d}-v5e"
        model = f"org/model-{i:03d}"
        cluster.create(Deployment(
            metadata=ObjectMeta(name=name, namespace=NS),
            replicas=1,
            selector={"app": name},
            template=PodTemplateSpec(
                labels={"app": name},
                containers=[Container(
                    name="srv",
                    args=["--max-num-batched-tokens=8192",
                          "--max-num-seqs=256"],
                    resources=ResourceRequirements(
                        requests={"google.com/tpu": "8"}))]),
            status=DeploymentStatus(replicas=1, ready_replicas=1)))
        cluster.create(VariantAutoscaling(
            metadata=ObjectMeta(
                name=name, namespace=NS,
                labels={"inference.optimization/acceleratorName": "v5e-8"}),
            spec=VariantAutoscalingSpec(
                scale_target_ref=CrossVersionObjectReference(name=name),
                model_id=model, variant_cost="10.0")))
        cluster.create(Pod(
            metadata=ObjectMeta(
                name=f"{name}-0", namespace=NS, labels={"app": name},
                owner_references=[{"kind": "Deployment", "name": name}]),
            status=PodStatus(phase="Running", ready=True,
                             pod_ip=f"10.0.{i}.1")))
        pod_labels = {"pod": f"{name}-0", "namespace": NS, "model_name": model}
        tsdb.add_sample("vllm:kv_cache_usage_perc", pod_labels, kv)
        tsdb.add_sample("vllm:num_requests_waiting", pod_labels, queue)
        tsdb.add_sample("vllm:cache_config_info",
                        {**pod_labels, "num_gpu_blocks": "4096",
                         "block_size": "32"}, 1.0)

    mgr = build_manager(cluster, cfg, clock=clock, tsdb=tsdb)
    mgr.setup()
    return mgr, cluster, tsdb, clock


# --- 1. API-request budget ---


def test_informer_tick_issues_zero_lists():
    """With the watch-backed informer (default on), a steady-state engine
    tick issues ZERO list requests — the snapshot's per-kind LIST is served
    from the watch-fed store (docs/design/informer.md)."""
    n = 20
    mgr, cluster, tsdb, clock = make_fleet_world(n)
    mgr.run_once()  # warm: first tick also exercises reconciler setup paths
    cluster.reset_request_counts()
    clock.advance(5.0)
    mgr.engine.optimize()  # one bare engine tick, no reconciler noise
    counts = cluster.request_counts()
    for kind in ("VariantAutoscaling", "Deployment", "LeaderWorkerSet",
                 "Pod"):
        assert counts.get(("list", kind), 0) == 0, counts
        assert counts.get(("get", kind), 0) == 0, counts


def test_tick_issues_o_kinds_lists_and_zero_per_va_gets():
    """Informer OFF: a 20-VA tick costs one LIST per touched kind — not one
    GET per VA per stage (the pre-snapshot loop issued 3+ Deployment/VA
    GETs per VA per tick)."""
    n = 20
    mgr, cluster, tsdb, clock = make_fleet_world(n, informer=False,
                                                 incremental=False)
    mgr.run_once()  # warm: first tick also exercises reconciler setup paths
    cluster.reset_request_counts()
    mgr.engine.optimize()  # one bare engine tick, no reconciler noise
    counts = cluster.request_counts()

    assert counts.get(("list", "VariantAutoscaling"), 0) == 1
    assert counts.get(("list", "Deployment"), 0) == 1
    # A Deployment-only fleet never touches LeaderWorkerSets (lazy LISTs).
    assert counts.get(("list", "LeaderWorkerSet"), 0) == 0
    # The load-bearing assertion: zero per-VA GETs on the happy path.
    assert counts.get(("get", "VariantAutoscaling"), 0) == 0
    assert counts.get(("get", "Deployment"), 0) == 0
    assert counts.get(("get", "LeaderWorkerSet"), 0) == 0


def _tick_read_counts(n):
    mgr, cluster, tsdb, clock = make_fleet_world(n, informer=False,
                                                 incremental=False)
    mgr.run_once()
    cluster.reset_request_counts()
    mgr.engine.optimize()
    return {k: v for k, v in cluster.request_counts().items()
            if k[0] in ("get", "list")
            and k[1] in ("VariantAutoscaling", "Deployment",
                         "LeaderWorkerSet")}


def test_tick_request_budget_independent_of_fleet_size():
    """Above the LIST threshold, K8s read requests per tick are identical
    for 10 and 20 VAs (writes still scale with material status changes,
    which is intended)."""
    assert _tick_read_counts(10) == _tick_read_counts(20)


def test_small_fleet_uses_memoized_targeted_gets_not_lists():
    """Below SNAPSHOT_LIST_MIN_VAS (informer off) the tick must NOT list
    scale-target kinds (shared clusters: thousands of foreign Deployments)
    — each target costs ONE memoized GET per tick despite being read by
    3-5 stages."""
    counts = _tick_read_counts(3)
    assert counts.get(("list", "Deployment"), 0) == 0
    assert counts.get(("get", "Deployment"), 0) == 3
    assert counts.get(("list", "VariantAutoscaling"), 0) == 1
    assert counts.get(("get", "VariantAutoscaling"), 0) == 0


def test_legacy_mode_still_pays_per_va_gets():
    """The bench's pre-change comparison lever really reproduces the old
    request shape (guards the bench-tick speedup claim's denominator)."""
    mgr, cluster, tsdb, clock = make_fleet_world(5, informer=False,
                                                 incremental=False)
    mgr.engine.tick_snapshot_enabled = False
    mgr.run_once()
    cluster.reset_request_counts()
    mgr.engine.optimize()
    counts = cluster.request_counts()
    assert counts.get(("get", "Deployment"), 0) >= 5


# --- 2. Determinism under parallelism ---


def _run_fleet(analysis_workers: int, ticks: int = 3):
    from wva_tpu.engines import common

    # The DecisionCache + trigger queue are process-global; stale entries
    # from other tests (same variant names, overlapping cycle ids) would
    # leak into the reconciler's post-tick trace events and break the byte
    # comparison.
    common.DecisionCache.clear()
    while not common.DecisionTrigger.empty():
        common.DecisionTrigger.get_nowait()
    mgr, cluster, tsdb, clock = make_fleet_world(
        4, kv=0.78, queue=2, analysis_workers=analysis_workers, trace=True)
    assert mgr.engine.analysis_workers == analysis_workers
    for _ in range(ticks):
        mgr.run_once()
        clock.advance(5.0)
    mgr.flight_recorder.flush()
    cycles = mgr.flight_recorder.snapshot()
    statuses = {
        va.metadata.name: encode(va.status)
        for va in cluster.list("VariantAutoscaling", namespace=NS)}
    mgr.shutdown()
    return cycles, statuses


def test_decisions_and_trace_byte_identical_at_any_pool_width():
    """The worker pool must not change ONE byte of the engine's outputs:
    decisions, VA statuses, and flight-recorder cycle records are compared
    via canonical JSON between a serial run and an 8-wide run."""
    serial_cycles, serial_statuses = _run_fleet(analysis_workers=1)
    pooled_cycles, pooled_statuses = _run_fleet(analysis_workers=8)

    assert len(serial_cycles) > 0 and serial_statuses

    def dumps(x):
        return json.dumps(x, sort_keys=True, separators=(",", ":"))

    assert dumps(serial_statuses) == dumps(pooled_statuses)
    assert len(serial_cycles) == len(pooled_cycles)
    for a, b in zip(serial_cycles, pooled_cycles):
        assert dumps(a) == dumps(b)


def _run_forecast_fleet(analysis_workers: int, ticks: int = 5):
    """Like _run_fleet but on the V2 path with the forecast planner ACTIVE
    (default-on config): batched forecaster fits + planner state evolution
    run per tick, and must stay byte-deterministic at any pool width."""
    from wva_tpu.engines import common

    common.DecisionCache.clear()
    while not common.DecisionTrigger.empty():
        common.DecisionTrigger.get_nowait()
    mgr, cluster, tsdb, clock = make_fleet_world(
        4, kv=0.78, queue=2, analysis_workers=analysis_workers, trace=True,
        saturation_cfg=SaturationScalingConfig(
            analyzer_name="saturation",
            anticipation_horizon_seconds=120.0))
    assert mgr.engine.forecast is not None, \
        "forecast planner must be on by default"
    for _ in range(ticks):
        mgr.run_once()
        clock.advance(15.0)
    mgr.flight_recorder.flush()
    cycles = mgr.flight_recorder.snapshot()
    statuses = {
        va.metadata.name: encode(va.status)
        for va in cluster.list("VariantAutoscaling", namespace=NS)}
    mgr.shutdown()
    return cycles, statuses


def test_forecast_fits_byte_identical_at_any_pool_width():
    """Forecast-plane determinism (docs/design/forecast.md): the planner
    runs on the engine thread in sorted model order and its batched JAX
    fits are row-independent, so a forecast-active V2 world produces
    byte-identical decisions, statuses, AND forecast stage events at
    worker-pool width 1 and 8."""
    serial_cycles, serial_statuses = _run_forecast_fleet(analysis_workers=1)
    pooled_cycles, pooled_statuses = _run_forecast_fleet(analysis_workers=8)

    assert len(serial_cycles) > 0 and serial_statuses
    assert any(ev.get("stage") == "forecast"
               for rec in serial_cycles for ev in rec.get("stages", [])), \
        "the V2 world must actually record forecast stage events"

    def dumps(x):
        return json.dumps(x, sort_keys=True, separators=(",", ":"))

    assert dumps(serial_statuses) == dumps(pooled_statuses)
    assert len(serial_cycles) == len(pooled_cycles)
    for a, b in zip(serial_cycles, pooled_cycles):
        assert dumps(a) == dumps(b)


# --- 3. Cross-model solver batching numerics ---


@pytest.mark.parametrize("split", [(1, 3), (2, 2)])
def test_batched_sizing_matches_per_model_sizing(split):
    """One padded cross-model sizing call must return the same per-replica
    capacities as one call per model (padding/bucketing cannot leak between
    candidates)."""
    from wva_tpu.analyzers.queueing import (
        PerfProfile,
        QueueingModelAnalyzer,
        ServiceParms,
        TargetPerf,
    )
    from wva_tpu.analyzers.queueing.analyzer import _Candidate, RequestSize

    analyzer = QueueingModelAnalyzer()

    def candidate(i):
        return _Candidate(
            variant_name=f"v{i}", accelerator="v5e-8", cost=10.0,
            ready=1, pending=0,
            profile=PerfProfile(
                model_id=f"m{i}", accelerator="v5e-8",
                service_parms=ServiceParms(alpha=18.0 + i, beta=0.00267,
                                           gamma=0.00002),
                max_batch_size=96, max_queue_size=384),
            targets=TargetPerf(target_ttft_ms=1000.0),
            request_size=RequestSize(avg_input_tokens=512.0 + 16 * i,
                                     avg_output_tokens=256.0))

    cands = [candidate(i) for i in range(sum(split))]
    batched = analyzer.size_candidates(cands)
    n0 = split[0]
    per_model = (analyzer.size_candidates(cands[:n0])
                 + analyzer.size_candidates(cands[n0:]))
    assert batched == pytest.approx(per_model, rel=1e-6)


# --- 4. Snapshot client semantics ---


def _mini_cluster():
    clock = FakeClock(start=1000.0)
    cluster = FakeCluster(clock=clock)
    for i in range(3):
        cluster.create(VariantAutoscaling(
            metadata=ObjectMeta(name=f"va{i}", namespace=NS),
            spec=VariantAutoscalingSpec(
                scale_target_ref=CrossVersionObjectReference(name=f"va{i}"),
                model_id=f"m{i}")))
    return cluster


def test_snapshot_serves_gets_from_one_list():
    cluster = _mini_cluster()
    snap = SnapshotKubeClient(cluster)
    cluster.reset_request_counts()
    for i in range(3):
        snap.get("VariantAutoscaling", NS, f"va{i}")
    assert snap.list("VariantAutoscaling", namespace=NS)
    counts = cluster.request_counts()
    assert counts == {("list", "VariantAutoscaling"): 1}
    assert snap.kinds_listed() == ["VariantAutoscaling"]


def test_snapshot_returns_isolated_copies():
    cluster = _mini_cluster()
    snap = SnapshotKubeClient(cluster)
    a = snap.get("VariantAutoscaling", NS, "va0")
    # Zero-copy snapshot reads are frozen shared views: mutation raises,
    # and a thawed clone never reaches the cache.
    with pytest.raises(FrozenObjectError):
        a.spec.model_id = "mutated"
    b = clone(a)
    b.spec.model_id = "mutated"
    assert snap.get("VariantAutoscaling", NS, "va0").spec.model_id == "m0"


def test_snapshot_read_your_writes_within_tick():
    cluster = _mini_cluster()
    snap = SnapshotKubeClient(cluster)
    va = clone(snap.get("VariantAutoscaling", NS, "va0"))
    va.status.desired_optimized_alloc.num_replicas = 7
    snap.update_status(va)
    assert snap.get("VariantAutoscaling", NS, "va0") \
        .status.desired_optimized_alloc.num_replicas == 7
    # ...and actually persisted to the backing cluster.
    assert cluster.get("VariantAutoscaling", NS, "va0") \
        .status.desired_optimized_alloc.num_replicas == 7


def test_snapshot_is_frozen_until_targeted_refresh():
    cluster = _mini_cluster()
    snap = SnapshotKubeClient(cluster)
    snap.get("VariantAutoscaling", NS, "va0")  # populate the kind cache
    # Out-of-band write (another controller): invisible to the tick...
    fresh = clone(cluster.get("VariantAutoscaling", NS, "va0"))
    fresh.status.desired_optimized_alloc.num_replicas = 42
    cluster.update_status(fresh)
    assert snap.get("VariantAutoscaling", NS, "va0") \
        .status.desired_optimized_alloc.num_replicas == 0
    # ...until the conflict path refreshes exactly that object.
    cluster.reset_request_counts()
    got = snap.refresh("VariantAutoscaling", NS, "va0")
    assert got.status.desired_optimized_alloc.num_replicas == 42
    assert cluster.request_counts() == {("get", "VariantAutoscaling"): 1}


def test_conflict_refetch_status_write_retries_with_targeted_get():
    """A REAL 409 through FakeCluster (stale resourceVersion from an older
    read, exactly what a tick-start snapshot produces after a concurrent
    write): the plain backoff helper must surface the conflict, and the
    engine's conflict-refetch helper must win the write while preserving
    the other writer's (reconciler-owned) status fields."""
    from wva_tpu.utils.variant import update_va_status_with_backoff

    cluster = _mini_cluster()
    va = clone(cluster.get("VariantAutoscaling", NS, "va1"))  # stale-rv read
    va.status.desired_optimized_alloc.num_replicas = 3
    # Concurrent reconciler write lands before the engine's (the 409 cause):
    # its condition must SURVIVE the conflict-refetch merge — only the
    # engine-owned fields may be grafted onto the fresh read.
    other = clone(cluster.get("VariantAutoscaling", NS, "va1"))
    other.set_condition("TargetResolved", "False", "TargetNotFound",
                        "scale target missing", now=1000.0)
    cluster.update_status(other)

    with pytest.raises(ConflictError):
        update_va_status_with_backoff(cluster, va)

    _, persisted = update_va_status_with_conflict_refetch(cluster, va)
    assert persisted
    stored = cluster.get("VariantAutoscaling", NS, "va1")
    assert stored.status.desired_optimized_alloc.num_replicas == 3
    cond = stored.get_condition("TargetResolved")
    assert cond is not None and cond.status == "False"


def test_conflict_refetch_never_reverts_a_newer_decision():
    """A scale-from-zero wake landing between the engine's snapshot read
    and its status write must WIN: the fresh status carries an alloc newer
    than what the engine read (read_alloc_run_time anchors the guard, not
    the engine's just-stamped time), so the engine's stale write drops."""
    from wva_tpu.api.v1alpha1 import OptimizedAlloc

    cluster = _mini_cluster()
    # Engine's snapshot read (alloc last_run_time = 0: never decided).
    va = clone(cluster.get("VariantAutoscaling", NS, "va2"))
    read_alloc = va.status.desired_optimized_alloc
    # Mid-tick wake: desired 0 -> 1, stamped t=50.
    wake = clone(cluster.get("VariantAutoscaling", NS, "va2"))
    wake.status.desired_optimized_alloc = OptimizedAlloc(
        accelerator="v5e-8", num_replicas=1, last_run_time=50.0)
    cluster.update_status(wake)
    # Engine computes desired=0 from the PRE-wake snapshot, stamped LATER
    # (t=60) — its own stamp postdates the wake, the read baseline doesn't.
    va.status.desired_optimized_alloc = OptimizedAlloc(
        accelerator="v5e-8", num_replicas=0, last_run_time=60.0)
    _, persisted = update_va_status_with_conflict_refetch(
        cluster, va, read_alloc=read_alloc)
    # The drop is SIGNALED so callers skip DecisionCache/trigger/events —
    # otherwise the reconciler would re-apply the stale value fresh.
    assert not persisted
    stored = cluster.get("VariantAutoscaling", NS, "va2")
    assert stored.status.desired_optimized_alloc.num_replicas == 1  # wake won


def test_conflict_refetch_heartbeat_is_not_a_newer_decision():
    """A heartbeat write re-stamps last_run_time with UNCHANGED values; a
    scale-from-zero wake racing it must still win its write — a newer
    timestamp alone is not a newer decision."""
    from wva_tpu.api.v1alpha1 import OptimizedAlloc

    cluster = _mini_cluster()
    # Wake's fresh read: desired=0 at t=10.
    va = clone(cluster.get("VariantAutoscaling", NS, "va0"))
    va.status.desired_optimized_alloc = OptimizedAlloc(
        accelerator="v5e-8", num_replicas=0, last_run_time=10.0)
    cluster.update_status(va)
    wake = clone(cluster.get("VariantAutoscaling", NS, "va0"))
    read_alloc = wake.status.desired_optimized_alloc
    # Engine heartbeat lands in between: same values, newer stamp (t=40).
    hb = clone(cluster.get("VariantAutoscaling", NS, "va0"))
    hb.status.desired_optimized_alloc = OptimizedAlloc(
        accelerator="v5e-8", num_replicas=0, last_run_time=40.0)
    cluster.update_status(hb)
    # The wake writes desired=1; its 409 must merge+retry, not drop.
    wake.status.desired_optimized_alloc = OptimizedAlloc(
        accelerator="v5e-8", num_replicas=1, last_run_time=30.0)
    _, persisted = update_va_status_with_conflict_refetch(
        cluster, wake, read_alloc=read_alloc)
    assert persisted
    stored = cluster.get("VariantAutoscaling", NS, "va0")
    assert stored.status.desired_optimized_alloc.num_replicas == 1
